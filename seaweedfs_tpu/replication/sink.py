"""Replication sinks: where replayed filer events land.

Reference: weed/replication/sink/ — ReplicationSink interface
(CreateEntry/UpdateEntry/DeleteEntry) with filersink (another cluster),
localsink (a directory), plus cloud sinks (S3/GCS/Azure/B2) that map to
the same three ops.  The S3 sink here targets any S3 endpoint — including
this framework's own gateway — over plain HTTP.
"""

from __future__ import annotations

import os
import shutil
import urllib.error
import urllib.parse
import urllib.request

from ..pb import filer_pb2
from ..util import connpool, failsafe, faultpoint

# fires before every replication apply (sink create/delete, geo apply):
# chaos arms it to model a dying target mid-replication — ctx is the
# destination path so `match` can target one object
FP_REPLICATION_APPLY = faultpoint.register("replication.apply")


class SinkPermanentError(Exception):
    """The target rejected the apply for good (4xx, bad request):
    retrying the same event can never succeed.  Callers count it and move
    on instead of wedging the stream on one poison event."""


# sink applies are IDEMPOTENT upserts: a PUT of the same bytes to the
# same path, or a DELETE of the same path, lands in the same state no
# matter how many times it runs — so transient transport failures and
# 5xx NACKs retry safely, while 4xx answers classify as permanent
_SINK_POLICY = failsafe.RetryPolicy(max_attempts=3, base_delay=0.2,
                                    max_delay=2.0)


def _apply_request(method: str, url: str, body: bytes | None = None,
                   headers: dict | None = None, timeout: float = 60,
                   ignore_404: bool = False) -> None:
    """One sink apply over the connpool, failsafe-classified: transient
    failures retry under _SINK_POLICY (via failsafe.call — same counter
    labels, same backoff discipline as every other retried path),
    permanent ones raise SinkPermanentError, everything else propagates
    for the caller's stream-level reconnect."""

    def attempt() -> None:
        try:
            with connpool.request(method, url, body=body,
                                  headers=headers or {},
                                  timeout=timeout) as r:
                r.read()
        except urllib.error.HTTPError as e:
            if ignore_404 and e.code == 404:
                return
            raise

    try:
        failsafe.call(attempt, op="apply", retry_type="replication",
                      policy=_SINK_POLICY)
    except Exception as e:  # noqa: BLE001 — permanence decided below
        _reason, retryable = failsafe.classify(e, idempotent=True)
        if not retryable:
            raise SinkPermanentError(
                f"{method} {url}: {_reason}: {e}") from e
        raise  # transients exhausted: stream-level reconnect retries


class Sink:
    def create_entry(self, directory: str, entry: filer_pb2.Entry,
                     data: bytes) -> None:
        raise NotImplementedError

    def update_entry(self, directory: str, entry: filer_pb2.Entry,
                     data: bytes) -> None:
        self.create_entry(directory, entry, data)

    def delete_entry(self, directory: str, name: str,
                     is_directory: bool) -> None:
        raise NotImplementedError


class LocalSink(Sink):
    """Mirror into a local directory tree (replication/sink/localsink)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, directory: str, name: str = "") -> str:
        rel = f"{directory.strip('/')}/{name}".strip("/")
        return os.path.join(self.root, rel) if rel else self.root

    def create_entry(self, directory, entry, data):
        path = self._path(directory, entry.name)
        if entry.is_directory:
            os.makedirs(path, exist_ok=True)
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)

    def delete_entry(self, directory, name, is_directory):
        path = self._path(directory, name)
        if is_directory:
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)


class FilerSink(Sink):
    """Replay into another filer cluster over its HTTP surface
    (replication/sink/filersink; data is re-uploaded so the target
    cluster owns its own chunks).

    ``signature`` marks every mutation this sink performs, so a metadata
    subscription on the TARGET filer with the same signature skips them —
    the loop-prevention contract of bidirectional filer.sync
    (command/filer_sync.go)."""

    def __init__(self, filer_http: str, signature: int = 0):
        self.filer_http = filer_http
        self.signature = signature

    def _url(self, directory: str, name: str = "",
             extra_q: str = "") -> str:
        path = f"{directory.rstrip('/')}/{name}" if name else directory
        if not path.startswith("/"):
            path = "/" + path
        q = []
        if self.signature:
            q.append(f"signature={self.signature}")
        if extra_q:
            q.append(extra_q)
        qs = ("?" + "&".join(q)) if q else ""
        return f"http://{self.filer_http}{urllib.parse.quote(path)}{qs}"

    def create_entry(self, directory, entry, data):
        if entry.is_directory:
            return  # target filer auto-creates parents on file writes
        faultpoint.inject(FP_REPLICATION_APPLY,
                          ctx=f"{directory}/{entry.name}")
        _apply_request(
            "PUT", self._url(directory, entry.name), body=data,
            headers={
                "Content-Type": entry.attributes.mime
                or "application/octet-stream"
            },
            timeout=120)

    def delete_entry(self, directory, name, is_directory):
        extra = "recursive=true&ignoreRecursiveError=true" if is_directory else ""
        faultpoint.inject(FP_REPLICATION_APPLY, ctx=f"{directory}/{name}")
        _apply_request("DELETE", self._url(directory, name, extra),
                       timeout=60, ignore_404=True)


class S3Sink(Sink):
    """Replay into an S3 bucket over plain HTTP (replication/sink/s3sink).

    Works unauthenticated against gateways with auth disabled (e.g. this
    framework's own s3 server in its default dev mode); for signed access
    front it with a proxy or extend with a signer.
    """

    def __init__(self, endpoint: str, bucket: str, prefix: str = ""):
        self.endpoint = endpoint
        self.bucket = bucket
        self.prefix = prefix.strip("/")

    def _key(self, directory: str, name: str = "") -> str:
        rel = f"{directory.strip('/')}/{name}".strip("/")
        return f"{self.prefix}/{rel}".strip("/") if self.prefix else rel

    def _url(self, key: str) -> str:
        return (f"http://{self.endpoint}/{self.bucket}/"
                f"{urllib.parse.quote(key)}")

    def create_entry(self, directory, entry, data):
        if entry.is_directory:
            return
        faultpoint.inject(FP_REPLICATION_APPLY,
                          ctx=f"{directory}/{entry.name}")
        _apply_request(
            "PUT", self._url(self._key(directory, entry.name)),
            body=data,
            headers={
                "Content-Type": entry.attributes.mime
                or "application/octet-stream"
            },
            timeout=120)

    def delete_entry(self, directory, name, is_directory):
        faultpoint.inject(FP_REPLICATION_APPLY, ctx=f"{directory}/{name}")
        _apply_request("DELETE", self._url(self._key(directory, name)),
                       timeout=60, ignore_404=True)


class SignedS3Sink(S3Sink):
    """S3Sink with SigV4 signing — the adapter shape the cloud sinks
    share (replication/sink/s3sink with credentials)."""

    def __init__(self, endpoint: str, bucket: str, access_key: str,
                 secret_key: str, region: str = "us-east-1",
                 prefix: str = "", scheme: str = "https"):
        super().__init__(endpoint, bucket, prefix)
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.scheme = scheme

    def _url(self, key: str) -> str:
        return (f"{self.scheme}://{self.endpoint}/{self.bucket}/"
                f"{urllib.parse.quote(key)}")

    def signed_headers(self, method: str, key: str,
                       body: bytes = b"") -> dict:
        from ..s3api.auth import sign_request

        return sign_request(
            method, self.endpoint,
            f"/{self.bucket}/{urllib.parse.quote(key)}", "s3",
            self.region, self.access_key, self.secret_key, body)

    def create_entry(self, directory, entry, data):
        if entry.is_directory:
            return
        key = self._key(directory, entry.name)
        headers = self.signed_headers("PUT", key, data)
        headers["Content-Type"] = (entry.attributes.mime
                                   or "application/octet-stream")
        req = urllib.request.Request(self._url(key), data=data,
                                     method="PUT", headers=headers)
        with urllib.request.urlopen(req, timeout=120) as r:
            r.read()

    def delete_entry(self, directory, name, is_directory):
        key = self._key(directory, name)
        req = urllib.request.Request(
            self._url(key), method="DELETE",
            headers=self.signed_headers("DELETE", key))
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                r.read()
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


class GcsSink(SignedS3Sink):
    """Google Cloud Storage via its S3-interoperability XML API with HMAC
    keys (replication/sink/gcssink's role; own transport)."""

    def __init__(self, bucket: str, access_key: str, secret_key: str,
                 prefix: str = ""):
        super().__init__("storage.googleapis.com", bucket, access_key,
                         secret_key, region="auto", prefix=prefix)


class B2Sink(SignedS3Sink):
    """Backblaze B2 via its S3-compatible endpoint
    (replication/sink/b2sink's role; own transport)."""

    def __init__(self, region: str, bucket: str, key_id: str,
                 application_key: str, prefix: str = ""):
        super().__init__(f"s3.{region}.backblazeb2.com", bucket, key_id,
                         application_key, region=region, prefix=prefix)


class AzureSink(Sink):
    """Azure Blob Storage with SharedKey signing
    (replication/sink/azuresink; the signature construction follows the
    public SharedKey spec and is testable offline)."""

    def __init__(self, account: str, account_key_b64: str, container: str,
                 prefix: str = ""):
        import base64 as _b64

        self.account = account
        self.key = _b64.b64decode(account_key_b64)
        self.container = container
        self.prefix = prefix.strip("/")

    def _key(self, directory: str, name: str = "") -> str:
        rel = f"{directory.strip('/')}/{name}".strip("/")
        return f"{self.prefix}/{rel}".strip("/") if self.prefix else rel

    def _url(self, key: str) -> str:
        return (f"https://{self.account}.blob.core.windows.net/"
                f"{self.container}/{urllib.parse.quote(key)}")

    def signed_headers(self, method: str, key: str, body: bytes = b"",
                       content_type: str = "") -> dict:
        import base64 as _b64
        import hashlib
        import hmac as _hmac
        import time as _time

        date = _time.strftime("%a, %d %b %Y %H:%M:%S GMT", _time.gmtime())
        headers = {
            "x-ms-date": date,
            "x-ms-version": "2020-10-02",
        }
        if method == "PUT":
            headers["x-ms-blob-type"] = "BlockBlob"
        canon_headers = "".join(
            f"{k}:{headers[k]}\n" for k in sorted(headers))
        canon_resource = (f"/{self.account}/{self.container}/"
                          f"{urllib.parse.quote(key)}")
        string_to_sign = "\n".join([
            method, "", "",
            str(len(body)) if body else "", "",
            content_type, "", "", "", "", "", "",
        ]) + "\n" + canon_headers + canon_resource
        sig = _b64.b64encode(_hmac.new(
            self.key, string_to_sign.encode(), hashlib.sha256).digest()
        ).decode()
        headers["Authorization"] = f"SharedKey {self.account}:{sig}"
        if content_type:
            headers["Content-Type"] = content_type
        return headers

    def create_entry(self, directory, entry, data):
        if entry.is_directory:
            return
        key = self._key(directory, entry.name)
        ctype = entry.attributes.mime or "application/octet-stream"
        req = urllib.request.Request(
            self._url(key), data=data, method="PUT",
            headers=self.signed_headers("PUT", key, data, ctype))
        with urllib.request.urlopen(req, timeout=120) as r:
            r.read()

    def delete_entry(self, directory, name, is_directory):
        key = self._key(directory, name)
        req = urllib.request.Request(
            self._url(key), method="DELETE",
            headers=self.signed_headers("DELETE", key))
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                r.read()
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


def sink_from_config(conf):
    """Build the one enabled [sink.*] of a replication.toml
    (replication/replicator.go + scaffold.go replication template).
    Returns (sink, label); raises if nothing is enabled."""
    if conf.get_bool("sink.local.enabled"):
        d = conf.get_string("sink.local.directory", "/backup")
        return LocalSink(d), f"local:{d}"
    if conf.get_bool("sink.filer.enabled"):
        addr = conf.get_string("sink.filer.grpcAddress", "localhost:18888")
        host, _, port_s = addr.partition(":")
        try:
            port = int(port_s)
        except ValueError:
            raise ValueError(
                f"[sink.filer] grpcAddress {addr!r} must be host:port"
            ) from None
        # the toml records the gRPC port (reference schema); the sink
        # speaks to the filer's HTTP port one offset below
        http_addr = f"{host}:{port - 10000}" if port > 10000 else addr
        return FilerSink(http_addr), f"filer:{addr}"
    if conf.get_bool("sink.s3.enabled"):
        endpoint = conf.get_string("sink.s3.endpoint", "localhost:8333")
        bucket = conf.get_string("sink.s3.bucket", "backup")
        return (S3Sink(endpoint, bucket,
                       prefix=conf.get_string("sink.s3.directory", "")),
                f"s3:{endpoint}/{bucket}")
    if conf.get_bool("sink.google_cloud_storage.enabled"):
        bucket = conf.get_string("sink.google_cloud_storage.bucket", "")
        return (GcsSink(bucket,
                        conf.get_string(
                            "sink.google_cloud_storage.access_key", ""),
                        conf.get_string(
                            "sink.google_cloud_storage.secret_key", ""),
                        prefix=conf.get_string(
                            "sink.google_cloud_storage.directory", "")),
                f"gcs:{bucket}")
    if conf.get_bool("sink.azure.enabled"):
        container = conf.get_string("sink.azure.container", "")
        return (AzureSink(conf.get_string("sink.azure.account_name", ""),
                          conf.get_string("sink.azure.account_key", ""),
                          container,
                          prefix=conf.get_string("sink.azure.directory", "")),
                f"azure:{container}")
    if conf.get_bool("sink.backblaze.enabled"):
        bucket = conf.get_string("sink.backblaze.bucket", "")
        return (B2Sink(conf.get_string("sink.backblaze.region",
                                       "us-west-002"),
                       bucket,
                       conf.get_string("sink.backblaze.b2_account_id", ""),
                       conf.get_string(
                           "sink.backblaze.b2_master_application_key", ""),
                       prefix=conf.get_string("sink.backblaze.directory",
                                              "")),
                f"b2:{bucket}")
    raise ValueError(
        f"no [sink.*] enabled in {conf.path or 'replication.toml'}")
